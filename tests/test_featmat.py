"""featmat quick tier: extraction units, matrix consistency, seeded
fatal regressions (deleted gate / de-aliased donation / peak-memory
blowup), golden artifacts — and the gate-driving rejection tests whose
literal clause IDs ARE the matrix's rejected-cell coverage.

The parametrized cases below drive every rejected cell/composition
through its real gate (tp_reject_reason / hier_reject_reason /
WorldSpec.validate / the CLI) and assert the bracketed ID, never the
prose — `python -m tools.featmat --check` fails CI if any rejected
clause loses its ID assertion under tests/.
"""
import dataclasses
import json
import os

import pytest

from tools.featmat.extract import (
    GATE_FILES, extract_module, extract_sites, sites_by_id,
)
from tools.featmat.matrix import (
    CELLS, COMPOSITIONS, FEATURES, RUNNERS, build_matrix,
    consistency_findings, matrix_json, render_markdown,
)
from tools.simlint.core import ModuleInfo

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _base_spec():
    from fognetsimpp_tpu.scenarios import smoke

    spec, _state, _net, _bounds = smoke.build(
        n_users=4, n_fogs=2, horizon=0.05, send_interval=0.01
    )
    return spec


# ----------------------------------------------------------------------
# extraction units
# ----------------------------------------------------------------------

def test_extraction_finds_definitions_with_roles():
    sites = extract_sites(ROOT)
    by_id = sites_by_id(sites)
    # engine-owned clause: one definition in the engine
    tp_chaos = by_id["TP-CHAOS"]
    assert [s.role for s in tp_chaos] == ["definition"]
    assert tp_chaos[0].relpath == "fognetsimpp_tpu/core/engine.py"
    # spec-owned clause defined in spec.py, cited by the CLI
    jt = by_id["SPEC-JOURNEYS-TELEM"]
    roles = {s.relpath: s.role for s in jt}
    assert roles["fognetsimpp_tpu/spec.py"] == "definition"
    assert roles["fognetsimpp_tpu/__main__.py"] == "citation"


def test_hier_template_synthesizes_at_call_sites():
    """hier_reject_reason's f-string template defines [TP-HIER] at the
    engine's call site and [FLEET-HIER] at the fleet's — and the
    federation module itself (template + docstring prose) contributes
    no sites at all."""
    sites = extract_sites(ROOT)
    by_id = sites_by_id(sites)
    tp_defs = [s for s in by_id["TP-HIER"] if s.role == "definition"]
    fl_defs = [s for s in by_id["FLEET-HIER"] if s.role == "definition"]
    assert [s.relpath for s in tp_defs] == ["fognetsimpp_tpu/core/engine.py"]
    assert [s.relpath for s in fl_defs] == [
        "fognetsimpp_tpu/parallel/fleet.py"
    ]
    assert not any("federation.py" in s.relpath for s in sites)


def test_docstring_mentions_are_not_sites():
    """Prose about an ID (module/function docstrings) is not a gate."""
    src = (
        '"""Module prose citing [TP-CHAOS] is not a gate."""\n'
        "def f():\n"
        '    """Nor is [CLI-SWEEP-TP] here."""\n'
        '    return "[TP-CHAOS] but this string IS a gate site"\n'
    )
    mod = ModuleInfo(
        "fognetsimpp_tpu/core/engine.py",
        "fognetsimpp_tpu/core/engine.py", src,
    )
    sites = extract_module(mod)
    assert [(s.id, s.line, s.role) for s in sites] == [
        ("TP-CHAOS", 4, "definition")
    ]


# ----------------------------------------------------------------------
# matrix consistency + seeded fatal regressions
# ----------------------------------------------------------------------

def test_matrix_is_clean():
    """The checked-in matrix, the gates, the hloaudit manifests and the
    tests corpus agree — zero findings (the CI gate's green state)."""
    assert consistency_findings(extract_sites(ROOT), ROOT) == []


def test_deleted_gate_clause_is_fatal():
    """Seeded regression: strip the [TP-CHAOS] clause out of the engine
    source — the matrix still claims the rejection, so featmat must
    report the deleted gate."""
    rel = "fognetsimpp_tpu/core/engine.py"
    sites = []
    for gf in GATE_FILES:
        full = os.path.join(ROOT, gf)
        with open(full, encoding="utf-8") as fh:
            src = fh.read()
        if gf == rel:
            src = src.replace("[TP-CHAOS] ", "")
        sites += extract_module(ModuleInfo(full, gf, src))
    findings = consistency_findings(sites, ROOT)
    assert any(
        f.startswith("deleted gate: [TP-CHAOS]") for f in findings
    ), findings
    # and ONLY that gate regressed
    assert all("[TP-CHAOS]" in f or "untested" not in f for f in findings)


def test_duplicate_definition_is_drift():
    sites = extract_sites(ROOT)
    dup = next(
        s for s in sites
        if s.id == "TP-CHAOS" and s.role == "definition"
    )
    findings = consistency_findings(
        sites + [dataclasses.replace(dup, line=dup.line + 1)], ROOT
    )
    assert any(
        f.startswith("drifting gate: [TP-CHAOS]") for f in findings
    ), findings


def test_dealiased_donation_is_fatal_a6():
    """Seeded regression: a donating variant whose compiled module lost
    its input_output_alias header must fail A6."""
    from tools.hloaudit.audit import check_donation_alias
    from tools.hloaudit.hlo import parse_hlo

    body = (
        "\n\nENTRY %main.1 (p0: f32[8]) -> f32[8] {\n"
        "  ROOT %add.1 = f32[8]{0} add(f32[8]{0} %p0, f32[8]{0} %p0)\n"
        "}\n"
    )
    aliased = parse_hlo(
        "HloModule m, input_output_alias={ {0}: (0, {}, may-alias) }"
        + body
    )
    dealiased = parse_hlo("HloModule m" + body)
    assert len(aliased.input_output_aliases) == 1
    assert aliased.input_output_aliases[0].param_number == 0
    # honoured donation: clean
    assert check_donation_alias(aliased, "v", donated=(1,)) == []
    # silently-declined donation: fatal
    bad = check_donation_alias(dealiased, "v", donated=(1,))
    assert len(bad) == 1 and bad[0].rule == "A6"
    # alias-count floor regression against the manifest: fatal
    floor = check_donation_alias(
        aliased, "v", donated=(1,),
        manifest={"aliases": 3, "min_aliases": 2},
    )
    assert len(floor) == 1 and "regressed" in floor[0].message
    # aliases on a variant that declares no donation: registry drift
    undecl = check_donation_alias(aliased, "v", donated=())
    assert len(undecl) == 1 and "no donation" in undecl[0].message


def test_peak_memory_blowup_is_fatal_a7():
    """Seeded regression: compiled peak bytes over the pinned budget
    must fail A7; missing budget is itself a finding; a backend with no
    memory stats skips."""
    from tools.hloaudit.audit import check_peak_memory

    mem = {"peak_bytes": 2048, "arg_bytes": 1024, "out_bytes": 512,
           "temp_bytes": 768, "alias_bytes": 256}
    assert check_peak_memory(mem, "v", budget=4096) == []
    blown = check_peak_memory(mem, "v", budget=1024)
    assert len(blown) == 1 and blown[0].rule == "A7"
    assert "2048 > budget 1024" in blown[0].message
    missing = check_peak_memory(mem, "v", budget=None)
    assert len(missing) == 1 and "no pinned peak-memory" in missing[0].message
    assert check_peak_memory(None, "v", budget=None) == []


def test_live_donating_variants_actually_alias():
    """The real A6 exemplars: the checked-in manifests of the donating
    programs pin non-zero alias floors."""
    for name in ("run_jit_donated", "fleet_step"):
        p = os.path.join(
            ROOT, "tools", "hloaudit", "manifests", f"{name}.json"
        )
        with open(p) as f:
            m = json.load(f)
        assert m["donated"], name
        assert m["aliases"] >= 1 and m["min_aliases"] >= 1, name


# ----------------------------------------------------------------------
# golden artifacts
# ----------------------------------------------------------------------

def test_features_md_golden():
    matrix = build_matrix(extract_sites(ROOT))
    with open(os.path.join(ROOT, "FEATURES.md")) as f:
        assert f.read() == render_markdown(matrix)


def test_matrix_json_checked_in_and_valid():
    matrix = build_matrix(extract_sites(ROOT))
    with open(os.path.join(ROOT, "tools", "featmat", "matrix.json")) as f:
        text = f.read()
    assert text == matrix_json(matrix)
    data = json.loads(text)
    assert data["runners"] == list(RUNNERS)
    # full feature x runner coverage, every cell exactly once
    got = {(c["feature"], c["runner"]) for c in data["cells"]}
    assert got == {(f, r) for f in FEATURES for r in RUNNERS}
    assert len(data["cells"]) == len(got)
    for c in data["cells"]:
        assert c["verdict"] in ("accepted", "rejected", "untracked")
        if c["verdict"] == "rejected":
            assert c["sites"], c  # a rejection must have live gate sites
            assert any(s["role"] == "definition" for s in c["sites"]), c
    for p in data["compositions"]:
        assert p["sites"], p


# ----------------------------------------------------------------------
# gate-driving rejection coverage (the rejected cells' ID assertions)
# ----------------------------------------------------------------------

# the bracketed literals below ARE the matrix's rejection coverage —
# featmat greps tests/ for exactly these `[ID]` forms (gate 3)
_TP_CASES = [
    ("[TP-NOFOGS]", dict(n_fogs=0)),
    ("[TP-CHAOS]", dict(chaos=True)),
    ("[TP-POOL]", dict(fog_model=1)),  # FogModel.POOL
    ("[TP-POLICY]", dict(policy=1)),  # Policy.ROUND_ROBIN: task-dependent
    ("[TP-ARRIVALS]", dict(two_stage_arrivals=False)),
    # [TP-WINDOW] deleted in ISSUE 18: windowed specs run the
    # distributed K-window selection (hop-pruned top-K exchange ring)
    ("[TP-DYNTOPO]", dict(assume_static=False)),
    ("[TP-ENERGY]", dict(energy_enabled=True)),
    ("[TP-WIRED]", dict(wired_queue_enabled=True)),
    ("[TP-SERIES]", dict(record_tick_series=True)),
    ("[TP-HIER]", dict(n_brokers=2)),
    # [TP-JOURNEYS] deleted in ISSUE 19: journey rings run shard-local
    # inside the sharded tick (tests/test_tp_journeys.py)
]


@pytest.mark.parametrize("clause,overrides", _TP_CASES,
                         ids=[c.strip("[]") for c, _ in _TP_CASES])
def test_tp_gate_leads_with_its_clause_id(clause, overrides):
    from fognetsimpp_tpu.core.engine import tp_reject_reason
    from fognetsimpp_tpu.spec import FogModel, Policy

    assert int(FogModel.POOL) == 1 and int(Policy.ROUND_ROBIN) == 1
    spec = dataclasses.replace(_base_spec(), **overrides)
    reason = tp_reject_reason(spec)
    assert reason is not None and reason.startswith(clause)


def test_tp_learn_clause_guards_behind_policy_gate(monkeypatch):
    """[TP-LEARN] is the defensive belt behind [TP-POLICY] (learned
    policies are not broker-dense); drive it by widening the dense
    family so the learner clause is what fires."""
    from fognetsimpp_tpu.core import engine
    from fognetsimpp_tpu.spec import Policy

    spec = dataclasses.replace(_base_spec(), policy=int(Policy.UCB))
    assert engine.tp_reject_reason(spec).startswith("[TP-POLICY]")
    monkeypatch.setattr(engine, "_broker_dense_ok", lambda s: True)
    assert engine.tp_reject_reason(spec).startswith("[TP-LEARN]")


def test_fleet_hier_gate_leads_with_its_clause_id():
    from fognetsimpp_tpu.hier.federation import hier_reject_reason

    spec = dataclasses.replace(_base_spec(), n_brokers=2)
    assert hier_reject_reason(spec, "fleet").startswith("[FLEET-HIER]")
    assert hier_reject_reason(_base_spec(), "fleet") is None


_SPEC_CASES = [
    ("[SPEC-STATIC-MAC]", dict(assume_static=True, mac_keyed=True)),
    ("[SPEC-JOURNEYS-TELEM]",
     dict(telemetry=False, telemetry_journeys=4)),
    ("[SPEC-CHAOS-STATIC]", dict(chaos=True, assume_static=True)),
    ("[SPEC-CHAOS-ENERGY]",
     dict(chaos=True, assume_static=False, energy_enabled=True)),
    ("[SPEC-HIER-POLICY]", dict(n_brokers=2, policy=1)),  # ROUND_ROBIN
]


@pytest.mark.parametrize("clause,overrides", _SPEC_CASES,
                         ids=[c.strip("[]") for c, _ in _SPEC_CASES])
def test_spec_validate_leads_with_its_clause_id(clause, overrides):
    spec = dataclasses.replace(_base_spec(), **overrides)
    with pytest.raises(ValueError) as e:
        spec.validate()
    assert clause in str(e.value)


_SWEEP = ["--sweep", "policies=min_busy loads=0.05"]
_CLI_ERROR_CASES = [
    ("[CLI-SWEEP-TP]", ["--tp", "8", *_SWEEP]),
    ("[CLI-SWEEP-HIER]", ["--brokers", "2", *_SWEEP]),
    ("[CLI-SWEEP-SERIES]", ["--ticks", *_SWEEP]),
    ("[CLI-SWEEP-TELEM]", ["--telemetry", *_SWEEP]),
    ("[CLI-SWEEP-SERVE]", ["--hist", *_SWEEP]),
    ("[CLI-CHECKIFY-SOLO]", ["--checkify", "--progress", "4"]),
    ("[CLI-SERVE-SERIES]", ["--serve", "0", "--progress", "4"]),
    ("[CLI-SERVE-FLEET]", ["--serve", "0", "--replicas", "8"]),
    ("[CLI-FLEET-PROGRESS]", ["--replicas", "8", "--progress", "4"]),
    ("[CLI-FLEET-TRAILS]", ["--replicas", "8", "--trails", "out.svg"]),
    ("[CLI-PROGRESS-SERIES]", ["--progress", "4", "--ticks"]),
]


@pytest.mark.parametrize("clause,argv", _CLI_ERROR_CASES,
                         ids=[c.strip("[]") for c, _ in _CLI_ERROR_CASES])
def test_cli_guard_cites_its_clause_id(clause, argv, capsys):
    from fognetsimpp_tpu.__main__ import main

    args = ["--scenario", "smoke", "--set", "scenario.horizon=0.05",
            *argv]
    try:
        rc = main(args)
    except SystemExit as e:  # argparse ap.error() paths
        rc = e.code
    assert rc == 2
    assert clause in capsys.readouterr().err
