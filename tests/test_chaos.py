"""chaos/ — deterministic fault injection (ISSUE 12).

Gates: the zero-row ChaosState is inert (chaos-off bit-exactness across
every run entry, and an inert chaos-ON world perturbs not a single
non-chaos bit), fault schedules and outcomes are bit-identical across
run/run_jit/run_chunked, schedules replay exactly on host, down fogs
are unpickable, RE-OFFLOAD conserves tasks, LOSE counts losses, the
learn credit of a crashed pick resolves exactly-once (hypothesis
property), and on the scripted churn world the bandits beat every
static policy on mean latency (the chaos-under-load result
BENCHMARKS.md records).
"""
import dataclasses

import jax
import numpy as np
import pytest

from fognetsimpp_tpu import Policy, run
from fognetsimpp_tpu.scenarios import smoke
from fognetsimpp_tpu.spec import ChaosMode, Stage

SMALL = dict(n_users=2, n_fogs=2, send_interval=0.05, horizon=0.4,
             assume_static=False)

#: The three policy-family worlds of the telemetry/fused A/B discipline:
#: dense/fused broker, sequential compacted broker, learned bandit.
WORLDS = [
    dict(policy=int(Policy.MIN_BUSY)),
    dict(policy=int(Policy.LOCAL_FIRST), broker_mips=2048.0),
    dict(policy=int(Policy.DUCB)),
]

#: The scripted churn world (the ISSUE 12 acceptance world): fog 0 is
#: slow AND flaky — after every reboot it advertises busy=0, so stale-
#: view schedulers keep feeding it — while fogs 1-3 are fast and
#: stable.  RE-OFFLOAD with a generous retry budget: no task is ever
#: lost, the damage is pure latency, which is exactly what the learned
#: policies should minimise.
CHURN_SCRIPT = tuple(
    (0, round(0.3 * k + 0.15, 3), round(0.3 * k + 0.30, 3))
    for k in range(7)
)
CHURN = dict(
    n_users=2, n_fogs=4,
    fog_mips=(3000.0, 120000.0, 120000.0, 120000.0),
    send_interval=0.05, horizon=2.1, dt=1e-3, seed=0,
    chaos=True, chaos_mode=int(ChaosMode.REOFFLOAD),
    chaos_script=CHURN_SCRIPT, chaos_max_retries=8,
    learn_explore=0.1, learn_discount=0.999,
)


def _state_hash(state) -> str:
    import hashlib

    h = hashlib.sha256()
    for leaf in jax.tree.leaves(state):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()


def _build(**kw):
    args = dict(SMALL)
    args.update(kw)
    return smoke.build(**args)


def _census(final) -> dict:
    stage = np.asarray(final.tasks.stage)
    return {s.name: int((stage == int(s)).sum()) for s in Stage}


# ----------------------------------------------------------------------
# inert gate + determinism
# ----------------------------------------------------------------------

def test_chaos_off_bit_exact_across_run_entries():
    """With spec.chaos off (the default) every chaos leaf has zero
    rows, stays zero, and run / run_jit / run_chunked produce
    bit-identical final states — over the three policy-family worlds."""
    from fognetsimpp_tpu.core.engine import run_chunked, run_jit

    for kw in WORLDS:
        spec, state, net, bounds = _build(**kw)
        assert not spec.chaos
        assert spec.chaos_fogs == 0 and spec.chaos_tasks == 0
        ref, _ = run(spec, state, net, bounds)
        assert ref.chaos.next_down.shape == (0,)
        assert ref.chaos.retry.shape == (0,)
        assert int(np.asarray(ref.chaos.n_crashes)) == 0
        h_ref = _state_hash(ref)
        spec2, state2, net2, bounds2 = _build(**kw)
        assert _state_hash(run_jit(spec2, state2, net2, bounds2)) == h_ref
        spec3, state3, net3, bounds3 = _build(**kw)
        assert (
            _state_hash(run_chunked(spec3, state3, net3, bounds3, 170))
            == h_ref
        )


def test_chaos_inert_on_never_perturbs_the_simulation():
    """chaos=True with ZERO fault sources (no MTBF, no script, no RTT
    terms) is read-only: every non-chaos leaf of the final state is
    bit-equal to the chaos-off run of the same world — the chaos key is
    folded (not split) from the world key, so even the PRNG stream
    matches."""
    for kw in WORLDS:
        spec_off, s_off, net, bounds = _build(**kw)
        ref, _ = run(spec_off, s_off, net, bounds)
        spec_on, s_on, net2, bounds2 = _build(chaos=True, **kw)
        assert spec_on.chaos_fogs == spec_on.n_fogs
        got, _ = run(spec_on, s_on, net2, bounds2)
        for f in dataclasses.fields(ref):
            if f.name == "chaos":
                continue
            for a, b in zip(
                jax.tree.leaves(getattr(ref, f.name)),
                jax.tree.leaves(getattr(got, f.name)),
            ):
                np.testing.assert_array_equal(
                    np.asarray(a), np.asarray(b), err_msg=f.name
                )
        # and the chaos counters themselves stayed zero
        for c in ("n_crashes", "n_lost_crash", "n_reoffloaded"):
            assert int(np.asarray(getattr(got.chaos, c))) == 0


ACTIVE = dict(
    chaos=True, chaos_mode=int(ChaosMode.REOFFLOAD),
    chaos_mtbf_s=0.12, chaos_mttr_s=0.05, chaos_max_retries=3,
    chaos_script=((1, 0.05, 0.1),),
    chaos_rtt_amp=0.5, chaos_rtt_burst_prob=0.05,
    n_fogs=3, horizon=0.8,
)


def test_active_chaos_bit_identical_across_run_entries():
    """Crash/recover schedules and fault outcomes are bit-identical
    across run / run_jit / run_chunked for a fixed seed (the schedules
    ride the carry; RTT bursts are keyed on the tick index)."""
    from fognetsimpp_tpu.core.engine import run_chunked, run_jit

    spec, state, net, bounds = _build(**ACTIVE)
    ref, _ = run(spec, state, net, bounds)
    assert int(np.asarray(ref.chaos.n_crashes)) > 0
    h_ref = _state_hash(ref)
    spec2, state2, net2, bounds2 = _build(**ACTIVE)
    assert _state_hash(run_jit(spec2, state2, net2, bounds2)) == h_ref
    for chunk in (101, 333):
        spec3, state3, net3, bounds3 = _build(**ACTIVE)
        assert (
            _state_hash(
                run_chunked(spec3, state3, net3, bounds3, chunk)
            )
            == h_ref
        )


def test_phase_contract_registered():
    from fognetsimpp_tpu.core.contracts import check_phase_contracts

    spec, state, net, _ = _build(**ACTIVE)
    checked = check_phase_contracts(spec, state, net)
    assert "_phase_chaos" in checked


# ----------------------------------------------------------------------
# schedules: host replay + masking
# ----------------------------------------------------------------------

def test_random_schedule_matches_host_timeline():
    """The device carry machine and the host replay consume the same
    fold_in stream: per-fog down-tick counts derived from the host
    timeline equal the device's down_ticks accumulator exactly."""
    from fognetsimpp_tpu.chaos import outage_timeline

    kw = dict(
        chaos=True, chaos_mtbf_s=0.1, chaos_mttr_s=0.04,
        chaos_seed=7, n_fogs=3, horizon=1.0,
    )
    spec, state, net, bounds = _build(**kw)
    final, _ = run(spec, state, net, bounds)
    timeline = outage_timeline(spec, final.chaos.key)
    assert timeline, "MTBF 0.1 over 1 s must produce outages"
    dt = spec.dt
    t1s = (np.arange(spec.n_ticks) + 1).astype(np.float32) * np.float32(dt)
    expect = np.zeros(spec.n_fogs, np.int64)
    for f, td, tu in timeline:
        # the device rule: down for the tick ending t1 iff td < t1 <= tu
        expect[f] += int(
            ((np.float32(td) < t1s) & (np.float32(tu) >= t1s)).sum()
        )
    np.testing.assert_array_equal(
        np.asarray(final.chaos.down_ticks, np.int64), expect
    )
    assert int(np.asarray(final.chaos.n_crashes)) == len(timeline)


@pytest.mark.parametrize(
    "policy",
    [int(Policy.MIN_BUSY), int(Policy.ROUND_ROBIN), int(Policy.RANDOM),
     int(Policy.DUCB)],
)
def test_down_fogs_are_unpickable(policy):
    """During a scripted outage no scheduler — argmin family or learned
    — ever routes a task to the down fog: every task assigned to fog 0
    was decided outside the outage window."""
    outage = (0, 0.1, 0.9)
    kw = dict(
        chaos=True, chaos_script=(outage,), n_fogs=2, horizon=1.0,
        policy=policy,
    )
    spec, state, net, bounds = _build(**kw)
    final, _ = run(spec, state, net, bounds)
    fog = np.asarray(final.tasks.fog)
    stage = np.asarray(final.tasks.stage)
    decided = stage > int(Stage.PUB_INFLIGHT)
    t_dec = np.asarray(final.tasks.t_at_broker)
    on0 = decided & (fog == 0)
    # decisions land at the end of the tick containing the arrival:
    # one dt of slack on each boundary
    in_outage = (t_dec > outage[1] + spec.dt) & (
        t_dec < outage[2] - spec.dt
    )
    assert not np.any(on0 & in_outage), (
        "a task was routed to a crashed fog"
    )
    assert int(np.asarray(final.metrics.n_completed)) > 0


# ----------------------------------------------------------------------
# in-flight handling: conservation + loss accounting
# ----------------------------------------------------------------------

def test_reoffload_conserves_tasks_on_the_churn_world():
    """The acceptance conservation check: on the scripted churn bench
    world in RE-OFFLOAD mode, spawned = completed + dropped + lost +
    in-flight with ZERO crash losses — every swept task bounces and
    eventually completes or stays in flight."""
    spec, state, net, bounds = smoke.build(**CHURN)
    final, _ = run(spec, state, net, bounds)
    ch = final.chaos
    assert int(np.asarray(ch.n_crashes)) >= 6
    assert int(np.asarray(ch.n_reoffloaded)) > 0
    assert int(np.asarray(ch.n_lost_crash)) == 0
    assert int(np.asarray(ch.n_retry_exhausted)) == 0
    c = _census(final)
    published = int(np.asarray(final.metrics.n_published))
    terminal = (
        c["DONE"] + c["DROPPED"] + c["LOST"] + c["NO_RESOURCE"]
        + c["REJECTED"]
    )
    in_flight = (
        c["PUB_INFLIGHT"] + c["TASK_INFLIGHT"] + c["QUEUED"]
        + c["RUNNING"] + c["LOCAL_RUN"]
    )
    assert published == terminal + in_flight
    assert c["LOST"] == 0  # no uplink loss, no crash loss
    assert c["DONE"] == int(np.asarray(final.metrics.n_completed))


def test_lose_mode_counts_crash_losses_exactly():
    kw = dict(
        chaos=True, chaos_mode=int(ChaosMode.LOSE),
        chaos_script=((0, 0.1, 0.3), (1, 0.2, 0.35)),
        n_fogs=2, horizon=0.6,
    )
    spec, state, net, bounds = _build(**kw)
    final, _ = run(spec, state, net, bounds)
    lost = int(np.asarray(final.chaos.n_lost_crash))
    assert lost > 0
    c = _census(final)
    # the only loss source in this world is the crash sweep
    assert c["LOST"] == lost
    assert int(np.asarray(final.metrics.n_lost)) == 0
    published = int(np.asarray(final.metrics.n_published))
    terminal = c["DONE"] + c["DROPPED"] + c["LOST"] + c["NO_RESOURCE"]
    in_flight = (
        c["PUB_INFLIGHT"] + c["TASK_INFLIGHT"] + c["QUEUED"] + c["RUNNING"]
    )
    assert published == terminal + in_flight


def test_retry_budget_exhausts_into_loss():
    """chaos_max_retries=0 in RE-OFFLOAD mode: the first crash a task
    is swept by exhausts its budget — it is lost and counted in
    n_retry_exhausted, never n_lost_crash (the counters partition by
    mode)."""
    kw = dict(
        chaos=True, chaos_mode=int(ChaosMode.REOFFLOAD),
        chaos_max_retries=0, chaos_script=((0, 0.1, 0.3),),
        n_fogs=1, horizon=0.5,
    )
    spec, state, net, bounds = _build(**kw)
    final, _ = run(spec, state, net, bounds)
    exhausted = int(np.asarray(final.chaos.n_retry_exhausted))
    assert exhausted > 0
    assert int(np.asarray(final.chaos.n_reoffloaded)) == 0
    assert int(np.asarray(final.chaos.n_lost_crash)) == 0
    assert _census(final)["LOST"] == exhausted


# ----------------------------------------------------------------------
# learn-credit interaction: exactly-once resolution
# ----------------------------------------------------------------------

def _credit_invariant(final):
    """Every pick resolves at most once: total credited rows equal the
    observed-ack credits plus the crash penalties, and never exceed the
    pick count."""
    reward_cnt = float(np.sum(np.asarray(final.learn.reward_cnt)))
    picks = float(np.sum(np.asarray(final.learn.pick_count)))
    lat_cnt = float(np.asarray(final.learn.lat_cnt))
    penalties = float(
        np.asarray(final.chaos.n_lost_crash)
        + np.asarray(final.chaos.n_reoffloaded)
        + np.asarray(final.chaos.n_retry_exhausted)
    )
    assert reward_cnt == pytest.approx(lat_cnt + penalties), (
        reward_cnt, lat_cnt, penalties
    )
    assert reward_cnt <= picks + 1e-6


def _credit_case(seed, mode, retries):
    """One world of the exactly-once property: run it, check the
    invariant.  Shape-stable: (mode, retries) pick the compile, seeds
    are pure data (the test_properties.py discipline)."""
    kw = dict(
        chaos=True, chaos_mode=mode, chaos_max_retries=retries,
        chaos_mtbf_s=0.1, chaos_mttr_s=0.05,
        n_fogs=3, horizon=0.6, policy=int(Policy.DUCB), seed=seed,
    )
    spec, state, net, bounds = _build(**kw)
    final, _ = run(spec, state, net, bounds)
    _credit_invariant(final)
    if mode == int(ChaosMode.LOSE):
        # terminal rows carry the credited flag exactly once
        stage = np.asarray(final.tasks.stage)
        credited = np.asarray(final.learn.credited)
        lost = stage == int(Stage.LOST)
        assert np.all(credited[lost] == 1)


@pytest.mark.parametrize(
    "mode,retries",
    [(int(ChaosMode.LOSE), 2), (int(ChaosMode.REOFFLOAD), 0),
     (int(ChaosMode.REOFFLOAD), 2)],
)
def test_learn_credit_exactly_once_grid(mode, retries):
    """Deterministic grid of the exactly-once invariant (runs
    everywhere; the hypothesis variant below widens the seed space when
    the library is available)."""
    for seed in (0, 3, 5):
        _credit_case(seed, mode, retries)


def test_learn_credit_exactly_once_property():
    pytest.importorskip(
        "hypothesis", reason="hypothesis not installed; the grid "
        "variant above covers the invariant deterministically"
    )
    from hypothesis import given, settings, strategies as st

    @settings(max_examples=8, deadline=None)
    @given(
        seed=st.integers(0, 31),
        mode=st.sampled_from(
            [int(ChaosMode.LOSE), int(ChaosMode.REOFFLOAD)]
        ),
        retries=st.sampled_from([0, 2]),
    )
    def prop(seed, mode, retries):
        _credit_case(seed, mode, retries)

    prop()


# ----------------------------------------------------------------------
# the chaos-under-load result: bandits beat every static policy
# ----------------------------------------------------------------------

def test_bandits_beat_every_static_policy_under_churn():
    """The ISSUE 12 acceptance result, via the learn/eval.py harness:
    on the scripted churn world both DUCB and EXP3 achieve lower mean
    task latency than the best static policy.  The flaky fog advertises
    busy=0 after every reboot, so stale-view scheduling keeps feeding
    it; the bandits learn its true observed latency (completions AND
    zero-reward crash penalties) and route around it."""
    from fognetsimpp_tpu.learn.eval import (
        DEFAULT_STATICS,
        mean_task_latency_s,
        run_policy,
        static_oracle,
    )

    def build(policy, **kw):
        args = dict(CHURN)
        args.update(kw)
        args["policy"] = int(policy)
        return smoke.build(**args)

    best, static_means = static_oracle(build, statics=DEFAULT_STATICS)
    oracle = static_means[best]
    assert np.isfinite(oracle)
    for pol in (Policy.DUCB, Policy.EXP3):
        _, final, _ = run_policy(build, int(pol))
        learned = mean_task_latency_s(final)
        assert learned < oracle, (
            f"{pol.name} mean latency {learned * 1e3:.1f} ms did not "
            f"beat the best static ({Policy(best).name}, "
            f"{oracle * 1e3:.1f} ms) — statics: "
            f"{ {Policy(p).name: round(m * 1e3, 1) for p, m in static_means.items()} }"
        )
        # and it did so losslessly (RE-OFFLOAD conservation)
        assert int(np.asarray(final.chaos.n_lost_crash)) == 0
        assert int(np.asarray(final.chaos.n_retry_exhausted)) == 0


# ----------------------------------------------------------------------
# observability: watchdog, recorder, exposition, timeline, postmortem
# ----------------------------------------------------------------------

def test_watchdog_crash_loss_floor_pages():
    """A flapping fog eating tasks at a CONSTANT rate has z ~ 0 on
    every signal — the absolute crash-loss floor must page anyway (the
    defer_rate discipline), and the fog_down signal must be derived."""
    from fognetsimpp_tpu.telemetry.live import Watchdog

    wd = Watchdog(n_fogs=4, crash_loss_floor=1.0, row_ticks=1.0)
    fired_kinds = []
    lost = 0.0
    for chunk in range(6):
        rows = {
            "t": np.asarray([chunk * 0.1]),
            "q_len_total": np.asarray([4.0]),
            "n_busy": np.asarray([2.0]),
            "n_deferred": np.asarray([0.0]),
            "n_completed": np.asarray([10.0 * chunk]),
            "n_dropped": np.asarray([0.0]),
            "defer_total": np.asarray([0.0]),
            "n_fogs_down": np.asarray([1.0]),
            "lost_crash_total": np.asarray([lost]),
        }
        lost += 2.0  # constant 2 losses per row
        fired = wd.update_from_rows(rows, ticks_done=(chunk + 1) * 100)
        fired_kinds += [
            (a["signal"], a["kind"]) for a in fired
        ]
    assert ("crash_loss_rate", "floor") in fired_kinds
    assert "fog_down" in wd.last_signals
    assert wd.last_signals["fog_down"] == pytest.approx(0.25)


def test_watchdog_accepts_pre_chaos_rows():
    """Rows recorded by a pre-chaos build (no chaos columns) still feed
    the watchdog — the .get-safe contract postmortem relies on."""
    from fognetsimpp_tpu.telemetry.live import Watchdog

    wd = Watchdog(n_fogs=2)
    rows = {
        "t": np.asarray([0.1]),
        "q_len_total": np.asarray([1.0]),
        "n_busy": np.asarray([1.0]),
        "n_deferred": np.asarray([0.0]),
        "n_completed": np.asarray([5.0]),
        "n_dropped": np.asarray([0.0]),
    }
    wd.update_from_rows(rows, ticks_done=100)
    assert "fog_down" not in wd.last_signals
    assert "crash_loss_rate" not in wd.last_signals


def test_recorder_exposition_and_timeline_carry_chaos(tmp_path):
    """One chaos run through the full output layer: .sca.json chaos
    section, fns_chaos_* OpenMetrics families, the Perfetto
    fog-lifecycle track, and a flight-recorder manifest postmortem can
    read — all from the one chaos_summary() source."""
    import json

    from fognetsimpp_tpu.runtime.recorder import record_run
    from fognetsimpp_tpu.telemetry.live import FlightRecorder
    from tools.postmortem import load as pm_load, summarize as pm_summ

    kw = dict(
        chaos=True, chaos_mode=int(ChaosMode.LOSE),
        chaos_script=((0, 0.1, 0.3),), n_fogs=2, horizon=0.5,
        telemetry=True,
    )
    spec, state, net, bounds = _build(**kw)
    final, _ = run(spec, state, net, bounds)
    paths = record_run(str(tmp_path), spec, final, run_id="Chaos-0")
    sca = json.loads(open(paths["sca"]).read())
    assert sca["chaos"]["mode"] == "lose"
    assert sca["chaos"]["crashes"] >= 1
    assert sca["chaos"]["lost_crash"] == int(
        np.asarray(final.chaos.n_lost_crash)
    )
    assert len(sca["chaos"]["down_ticks"]) == spec.n_fogs
    om = open(paths["om"]).read()
    assert "fns_chaos_lost_crash" in om
    assert 'fns_chaos_fog_down_ticks{fog="0"}' in om
    # Perfetto fog-lifecycle track
    from fognetsimpp_tpu.telemetry.timeline import build_trace

    trace = build_trace(spec, final)
    downs = [
        e for e in trace["traceEvents"] if e.get("name") == "fog_down"
    ]
    assert len(downs) == 1 and downs[0]["tid"] == 0
    assert downs[0]["ts"] == pytest.approx(0.1e6)
    # flight-recorder manifest: chaos section present, loader .get-safe
    fr = FlightRecorder()
    fr.note_chunk(100, rows={}, state_hash="x")
    p = fr.dump(str(tmp_path), "test", spec=spec, final=final)
    d = pm_load(p)
    assert d["chaos"]["lost_crash"] == sca["chaos"]["lost_crash"]
    assert any("chaos:" in line for line in pm_summ(d))
    # an old-style manifest (no chaos key) still loads and summarizes
    old = tmp_path / "old.json"
    old.write_text(json.dumps({"reason": "nan", "ring": []}))
    assert pm_summ(pm_load(str(old)))


def test_cli_chaos_composes_with_policy_and_telemetry(tmp_path, capsys):
    """--chaos composes with --policy/--telemetry/--trace-out and the
    run lands chaos counters in every output."""
    import json

    from fognetsimpp_tpu.__main__ import main

    trace = tmp_path / "trace.json"
    rc = main([
        "--scenario", "smoke",
        "--set", "scenario.horizon=0.3",
        "--chaos", "flaky", "--chaos-seed", "3",
        "--policy", "ducb", "--telemetry",
        "--trace-out", str(trace),
        "--out", str(tmp_path),
    ])
    captured = capsys.readouterr()
    assert rc == 0
    json.loads(captured.out.splitlines()[-1])
    sca = json.loads((tmp_path / "General-0.sca.json").read_text())
    assert sca["chaos"]["mode"] == "lose"
    assert sca["spec"]["chaos_seed"] == 3
    json.loads(trace.read_text())


def test_serve_run_pages_on_crash_losses(tmp_path):
    """The live health plane over a LOSE-mode churn world: the
    crash-loss floor fires, the manifest carries chaos counters, and
    the chunk entries record the running counters (.get-safe extras)."""
    from fognetsimpp_tpu.telemetry.live import Watchdog, serve_run

    kw = dict(
        chaos=True, chaos_mode=int(ChaosMode.LOSE),
        chaos_mtbf_s=0.05, chaos_mttr_s=0.03, chaos_seed=1,
        n_users=4, n_fogs=2, horizon=0.8, telemetry=True,
        telemetry_reservoir=64,
    )
    spec, state, net, bounds = _build(**kw)
    stride = max(1, -(-spec.n_ticks // spec.telemetry_slots))
    final, status = serve_run(
        spec, state, net, bounds, chunk_ticks=100, port=None,
        dump_dir=str(tmp_path),
        # this tiny world loses a handful of tasks over 800 ticks: an
        # SLO-grade floor would stay silent, so page on any sustained
        # loss at all (production floors are per-deployment anyway)
        watchdog=Watchdog(
            spec.n_fogs, crash_loss_floor=0.005, row_ticks=stride
        ),
    )
    assert int(np.asarray(final.chaos.n_lost_crash)) > 0
    wd = status["watchdog"]
    assert "fog_down" in wd.last_signals
    kinds = {(a["signal"], a["kind"]) for a in wd.anomalies}
    assert ("crash_loss_rate", "floor") in kinds
    ring = status["recorder"].ring
    assert any("chaos" in entry for entry in ring)
