"""End-to-end smoke test: the wired 2-user/2-fog world runs and conserves.

Batched-engine rendition of the reference's wired integration smoke test
(`simulations/testing/omnetpp.ini` -> `Network`), with the property tests the
reference lacks (SURVEY.md §4 "implication": queue conservation, busyTime
sanity, monotone timestamps).
"""
import jax.numpy as jnp
import numpy as np
import pytest

from fognetsimpp_tpu import Stage, run
from fognetsimpp_tpu.scenarios import smoke


@pytest.fixture(scope="module")
def world():
    spec, state, net, bounds = smoke.build(horizon=2.0, send_interval=0.05)
    final, _ = run(spec, state, net, bounds)
    return spec, final


def test_tasks_flow_to_completion(world):
    spec, final = world
    stage = np.asarray(final.tasks.stage)
    done = (stage == int(Stage.DONE)).sum()
    published = int(final.metrics.n_published)
    # 2 users publishing every 50ms for 2s -> ~40 tasks each
    assert published >= 78
    # service times (200-900 MIPS over 1000/2000 MIPS fogs -> 0.1-0.9s)
    # vs arrival rate 40/s: heavy overload, so only a prefix completes —
    # but the serving chain must have made progress on both fogs
    assert done >= 3
    assert int(final.metrics.n_no_resource) == 0
    assert int(final.metrics.n_dropped) == 0


def test_timestamps_causal(world):
    spec, final = world
    t = final.tasks
    stage = np.asarray(t.stage)
    for mask_stage in (int(Stage.DONE),):
        m = stage == mask_stage
        if not m.any():
            continue
        t_create = np.asarray(t.t_create)[m]
        t_b = np.asarray(t.t_at_broker)[m]
        t_f = np.asarray(t.t_at_fog)[m]
        t_s = np.asarray(t.t_service_start)[m]
        t_c = np.asarray(t.t_complete)[m]
        t_a6 = np.asarray(t.t_ack6)[m]
        assert (t_create <= t_b).all()
        assert (t_b <= t_f).all()
        assert (t_f <= t_s + 1e-6).all()
        assert (t_s < t_c).all()
        assert (t_c < t_a6).all()


def test_task_conservation(world):
    """Every published task is in exactly one lifecycle stage; none vanish."""
    spec, final = world
    stage = np.asarray(final.tasks.stage)
    published = int(final.metrics.n_published)
    in_system = (stage != int(Stage.UNUSED)).sum()
    assert in_system == published
    # queued tasks are exactly the ones sitting in some fog ring
    q_total = int(np.asarray(final.fogs.q_len).sum())
    assert (stage == int(Stage.QUEUED)).sum() == q_total
    running = (stage == int(Stage.RUNNING)).sum()
    assert running == int((np.asarray(final.fogs.current_task) >= 0).sum())


def test_busy_time_nonnegative(world):
    spec, final = world
    busy = np.asarray(final.fogs.busy_time)
    assert (busy >= -1e-4).all()


def test_service_time_formula(world):
    """t_complete - t_service_start == MIPSRequired / fog MIPS
    (ComputeBrokerApp3.cc:276)."""
    spec, final = world
    t = final.tasks
    stage = np.asarray(t.stage)
    m = stage == int(Stage.DONE)
    fog = np.asarray(t.fog)[m]
    mips = np.asarray(final.fogs.mips)[fog]
    svc = np.asarray(t.t_complete)[m] - np.asarray(t.t_service_start)[m]
    np.testing.assert_allclose(svc, np.asarray(t.mips_req)[m] / mips, rtol=1e-4)


def test_latency_signals_recorded(world):
    spec, final = world
    t = final.tasks
    stage = np.asarray(t.stage)
    done = stage == int(Stage.DONE)
    # every done task has a finite ack6 (taskTime signal, mqttApp2.cc:282)
    assert np.isfinite(np.asarray(t.t_ack6)[done]).all()
    # every broker-decided task has the forwarded status-4 ack (latencyH1)
    decided = ~np.isin(stage, [int(Stage.UNUSED), int(Stage.PUB_INFLIGHT)])
    assert np.isfinite(np.asarray(t.t_ack4_fwd)[decided]).all()
    # latencies are positive and include two network hops
    lat_h1 = np.asarray(t.t_ack4_fwd)[decided] - np.asarray(t.t_create)[decided]
    assert (lat_h1 > 0).all()


def test_deterministic_same_seed():
    spec, state, net, bounds = smoke.build(horizon=0.3, seed=7)
    f1, _ = run(spec, state, net, bounds)
    spec2, state2, net2, bounds2 = smoke.build(horizon=0.3, seed=7)
    f2, _ = run(spec2, state2, net2, bounds2)
    np.testing.assert_array_equal(
        np.asarray(f1.tasks.t_ack6), np.asarray(f2.tasks.t_ack6)
    )
    np.testing.assert_array_equal(
        np.asarray(f1.tasks.mips_req), np.asarray(f2.tasks.mips_req)
    )


def test_checkify_sanitizer_smoke():
    """The opt-in runtime sanitizer (FNS_CHECKIFY / --checkify, ISSUE 7
    satellite): the default `div` set runs the smoke world clean AND
    bit-exact vs the plain path; the opt-in `nan` set demonstrably
    trips on the engine's deliberate inf-sentinel masked-lane
    arithmetic (the documented known-benign class — proving the error
    carry threads through the whole scan)."""
    from jax.experimental.checkify import JaxRuntimeError

    from fognetsimpp_tpu.core.engine import run_checkified

    spec, state, net, bounds = smoke.build(horizon=0.3, seed=7)
    ref, _ = run(spec, state, net, bounds)
    spec2, state2, net2, bounds2 = smoke.build(horizon=0.3, seed=7)
    final, _ = run_checkified(spec2, state2, net2, bounds2)  # default: div
    np.testing.assert_array_equal(
        np.asarray(ref.tasks.t_ack6), np.asarray(final.tasks.t_ack6)
    )
    spec3, state3, net3, bounds3 = smoke.build(horizon=0.3, seed=7)
    with pytest.raises(JaxRuntimeError):
        run_checkified(spec3, state3, net3, bounds3, errors="nan")
    with pytest.raises(ValueError):
        run_checkified(spec3, state3, net3, bounds3, errors="bogus")
