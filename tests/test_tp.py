"""TP sharded-tick gates (ISSUE 9, ``parallel/taskshard.run_tp_sharded``).

The acceptance contract of the million-user capacity path: the explicit
``shard_map``'d TP tick — per-user/per-task rows sharded over the
8-virtual-device ``node`` mesh, hand-placed broker↔fog collectives, ring
arrival exchange — must be BIT-EXACT vs the single-device reference
engine (state-hash A/B over the dense-broker policy-family worlds,
against ``run`` / ``run_jit`` / ``run_chunked``), with padding, chaining
and the exchange-window deferral contract each pinned separately.  The
ring exchange itself is unit-tested against a dense reference,
including the opt-in Pallas remote-DMA kernel in interpret mode.

Compile budget: every TP call here donates its carry (``donate=True``),
so the A/B doubles as the donated-carry bit-exactness gate AND the
worlds sharing a spec share one cached program (the padding test's
padded spec IS the MIN_BUSY world's spec).  The ``donate=False`` path
is covered by ``test_parallel.py``'s ``run_node_sharded`` dispatch.
"""
import dataclasses
import hashlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fognetsimpp_tpu import Policy, run
from fognetsimpp_tpu.core.engine import run_chunked, run_jit, tp_ok
from fognetsimpp_tpu.parallel import (
    make_mesh,
    pad_users_to_multiple,
    ring_all_gather,
    run_tp_sharded,
)
from fognetsimpp_tpu.parallel.tp import shard_map
from fognetsimpp_tpu.scenarios import smoke
from jax.sharding import PartitionSpec as P

SMALL = dict(
    n_users=16, n_fogs=3, send_interval=0.01, horizon=0.2,
    start_time_max=0.05,
)

#: The three dense-broker policy-family worlds the TP tick admits: the
#: faithful mips0-divisor argmin family (MIN_BUSY, MIN_LATENCY) and the
#: v1/v2 MAX_MIPS scan.
WORLDS = [
    dict(policy=int(Policy.MIN_BUSY)),
    # jitter exercises the full-width-draw-and-slice k_jit stream
    dict(policy=int(Policy.MIN_LATENCY), send_interval_jitter=0.1),
    dict(policy=int(Policy.MAX_MIPS)),
]


def _hash(state) -> str:
    h = hashlib.sha256()
    for leaf in jax.tree.leaves(state):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()


def _build(**kw):
    args = dict(SMALL)
    args.update(kw)
    return smoke.build(**args)


def _tp(spec, state, net, bounds, mesh, **kw):
    """All TP calls donate a copy: the run_jit memory discipline, and
    one cached program per (spec, ticks) across the module."""
    kw.setdefault("donate", True)
    return run_tp_sharded(
        spec, jax.tree.map(jnp.copy, state), net, bounds, mesh, **kw
    )


@pytest.fixture(scope="module")
def node_mesh():
    assert len(jax.devices()) == 8, "conftest must provision 8 devices"
    return make_mesh(8, axis_name="node")


def test_tp_gate_is_pinned():
    """The static TP family: dense-broker FIFO no-window static worlds."""
    on = _build()[0]
    assert tp_ok(on)
    assert tp_ok(_build(policy=int(Policy.MAX_MIPS))[0])
    assert not tp_ok(_build(policy=int(Policy.ROUND_ROBIN))[0])
    assert not tp_ok(_build(policy=int(Policy.UCB))[0])
    assert not tp_ok(
        _build(policy=int(Policy.LOCAL_FIRST), broker_mips=2048.0)[0]
    )
    # windowed compaction is TP-admitted since the distributed K-window
    # selection (the hop-pruned exchange ring) landed
    assert tp_ok(dataclasses.replace(on, arrival_window=8))
    assert not tp_ok(dataclasses.replace(on, two_stage_arrivals=False))
    assert not tp_ok(dataclasses.replace(on, assume_static=False))
    # telemetry composes, including the streaming latency histogram
    # (ISSUE 11: per-shard phase attribution + exchange-plane gauges;
    # tests/test_tp_telemetry.py owns the A/B gates)
    assert tp_ok(dataclasses.replace(on, telemetry=True))
    assert tp_ok(
        dataclasses.replace(
            on, telemetry=True, telemetry_hist=True, derive_acks=False
        )
    )


def test_tp_bitexact_vs_reference(node_mesh):
    """State-hash A/B over the three policy-family worlds, with the
    TP carry donated (bit-exactness is donation-independent)."""
    for kw in WORLDS:
        spec, state, net, bounds = _build(**kw)
        ref, _ = run(spec, state, net, bounds)
        spec2, got = _tp(spec, state, net, bounds, node_mesh)
        assert spec2 == spec
        assert _hash(ref) == _hash(got), kw
        # the table really is distributed over the mesh
        assert len(got.tasks.stage.sharding.device_set) == 8
        assert int(np.asarray(got.metrics.n_scheduled)) > 0


@pytest.mark.slow  # adds run_jit/run_chunked compiles + a half-horizon
#   TP program: full-suite tier (the quick tier keeps the 3-world A/B)
def test_tp_bitexact_vs_jit_and_chunked(node_mesh):
    """The sharded tick also matches the donated run_jit and the
    chunked runner (the same carry either way), and a chained pair of
    half-horizon TP calls matches one full-horizon TP run."""
    spec, state, net, bounds = _build()
    _, got = _tp(spec, state, net, bounds, node_mesh)
    jit_ref = run_jit(spec, jax.tree.map(jnp.copy, state), net, bounds)
    assert _hash(jit_ref) == _hash(got)
    chunk_ref = run_chunked(
        spec, jax.tree.map(jnp.copy, state), net, bounds,
        chunk_ticks=spec.n_ticks // 2,
    )
    assert _hash(chunk_ref) == _hash(got)
    n = spec.n_ticks
    assert n % 2 == 0  # both halves share one compiled program
    _, half = _tp(spec, state, net, bounds, node_mesh, n_ticks=n // 2)
    _, full = _tp(spec, half, net, bounds, node_mesh, n_ticks=n // 2)
    assert _hash(full) == _hash(got)


def test_pad_users_to_multiple_inert(node_mesh):
    """A non-divisible population pads with INERT users: the padded
    world bit-matches the single-device reference at the padded spec
    (which here IS the MIN_BUSY world's spec — one shared program), and
    the ghost rows never leave Stage.UNUSED."""
    spec, state, net, bounds = _build(n_users=13)
    spec_p, state_p, net_p = pad_users_to_multiple(spec, state, net, 8)
    assert spec_p.n_users == 16
    ref, _ = run(spec_p, state_p, net_p, bounds)
    spec2, got = _tp(spec, state, net, bounds, node_mesh)
    assert spec2 == spec_p
    assert _hash(ref) == _hash(got)
    S = spec_p.max_sends_per_user
    st = np.asarray(got.tasks.stage).reshape(16, S)
    assert (st[13:] == 0).all()  # ghosts stay UNUSED
    assert not np.asarray(got.users.connected)[13:].any()
    # real users published; ghosts never did
    assert (np.asarray(got.users.send_count)[:13] > 0).any()
    assert (np.asarray(got.users.send_count)[13:] == 0).all()
    # pad=False keeps the hard error (the GSPMD-era contract)
    with pytest.raises(ValueError, match="divide"):
        run_tp_sharded(spec, state, net, bounds, node_mesh, pad=False)


def test_tp_rejects_outside_family(node_mesh):
    spec, state, net, bounds = _build(policy=int(Policy.ROUND_ROBIN))
    with pytest.raises(ValueError, match="dense-broker"):
        run_tp_sharded(spec, state, net, bounds, node_mesh)


@pytest.mark.slow  # its own (coarse-dt) program: full-suite tier
def test_tp_multi_send_coarse_dt_bitexact(node_mesh):
    """dt > send_interval: the closed-form multi-send spawn's (U, R)
    draw lanes slice per shard bit-exactly (the windowed bench shape)."""
    spec, state, net, bounds = _build(dt=0.02, max_sends_per_tick=3)
    ref, _ = run(spec, state, net, bounds)
    _, got = _tp(spec, state, net, bounds, node_mesh)
    assert _hash(ref) == _hash(got)


@pytest.mark.slow  # its own (spec, window) program: full-suite tier
def test_exchange_window_defers_not_drops(node_mesh):
    """A starved exchange window defers arrivals (the engine's K-window
    contract): decisions land later ticks, nothing is lost, and the
    backlog gauge shows it."""
    spec, state, net, bounds = _build(start_time_max=0.0, horizon=0.15)
    ref, _ = run(spec, state, net, bounds)
    _, got = _tp(
        spec, state, net, bounds, node_mesh, exchange_window=1
    )
    assert int(np.asarray(got.metrics.n_deferred_max)) > 0
    # every publish still got decided and completed like the reference
    assert int(np.asarray(got.metrics.n_scheduled)) == int(
        np.asarray(ref.metrics.n_scheduled)
    )
    assert int(np.asarray(got.metrics.n_completed)) == int(
        np.asarray(ref.metrics.n_completed)
    )


# ----------------------------------------------------------------------
# distributed K-window selection (ISSUE 18): windowed specs on the TP
# path via the hop-pruned top-K exchange ring
# ----------------------------------------------------------------------

def test_tp_window_bitexact_vs_reference(node_mesh):
    """Windowed specs (arrival_window=K < task_capacity) run the
    distributed top-K exchange and stay bit-exact vs the single-device
    windowed engine — state-hash A/B over the three policy-family
    worlds, carry donated.  K=4 overflows on the SMALL worlds
    (n_deferred_max > 0 in the reference), so the tick-keyed rotation
    and the merged window's deferral accounting are both on the hook."""
    for kw in WORLDS:
        spec, state, net, bounds = _build(arrival_window=4, **kw)
        ref, _ = run(spec, state, net, bounds)
        spec2, got = _tp(spec, state, net, bounds, node_mesh)
        assert spec2 == spec
        assert _hash(ref) == _hash(got), kw
        assert len(got.tasks.stage.sharding.device_set) == 8
        assert int(np.asarray(got.metrics.n_scheduled)) > 0


def test_tp_window_padding_inert(node_mesh):
    """Padding composes with the windowed exchange: the padded window
    geometry (spec.window recomputed at the padded capacity) matches
    the single-device reference at the padded spec — which IS the
    windowed MIN_BUSY world's spec, sharing its cached program."""
    spec, state, net, bounds = _build(n_users=13, arrival_window=4)
    spec_p, state_p, net_p = pad_users_to_multiple(spec, state, net, 8)
    ref, _ = run(spec_p, state_p, net_p, bounds)
    spec2, got = _tp(spec, state, net, bounds, node_mesh)
    assert spec2 == spec_p
    assert _hash(ref) == _hash(got)


def test_tp_window_rejects_exchange_window(node_mesh):
    """exchange_window tunes the no-window ring only: a windowed spec
    already bounds the exchange to its own global K, so combining the
    two is a clear one-line error (no silent double-windowing)."""
    spec, state, net, bounds = _build(arrival_window=4)
    with pytest.raises(ValueError, match="exchange_window"):
        run_tp_sharded(
            spec, state, net, bounds, node_mesh, exchange_window=2
        )


@pytest.mark.slow  # adds run_jit/run_chunked compiles + a half-horizon
#   TP program on the windowed spec: full-suite tier
def test_tp_window_bitexact_vs_jit_and_chunked(node_mesh):
    """The windowed TP tick also matches the donated run_jit and the
    chunked runner, and a chained pair of half-horizon windowed TP
    calls matches one full-horizon run (the donated-carry gate)."""
    spec, state, net, bounds = _build(arrival_window=4)
    _, got = _tp(spec, state, net, bounds, node_mesh)
    jit_ref = run_jit(spec, jax.tree.map(jnp.copy, state), net, bounds)
    assert _hash(jit_ref) == _hash(got)
    chunk_ref = run_chunked(
        spec, jax.tree.map(jnp.copy, state), net, bounds,
        chunk_ticks=spec.n_ticks // 2,
    )
    assert _hash(chunk_ref) == _hash(got)
    n = spec.n_ticks
    assert n % 2 == 0
    _, half = _tp(spec, state, net, bounds, node_mesh, n_ticks=n // 2)
    _, full = _tp(spec, half, net, bounds, node_mesh, n_ticks=n // 2)
    assert _hash(full) == _hash(got)


@pytest.mark.slow  # its own (spec, window) program: full-suite tier
def test_tp_window_sustained_overflow_defers_not_drops(node_mesh):
    """Sustained exchange overflow under the merged path (ISSUE 18
    satellite): every user publishes at t=0 and the global window K=2
    is far below the steady-state candidate count, so the merge ring
    truncates every tick.  The drop-OLDEST/defer rotation fairness
    contract must hold exactly as on one device: arrivals defer
    (observable in n_deferred/n_deferred_max), nothing is lost, and the
    final state still bit-matches the single-device windowed engine."""
    spec, state, net, bounds = _build(
        start_time_max=0.0, horizon=0.15, arrival_window=2
    )
    ref, _ = run(spec, state, net, bounds)
    _, got = _tp(spec, state, net, bounds, node_mesh)
    assert _hash(ref) == _hash(got)
    assert int(np.asarray(got.metrics.n_deferred_max)) > 0
    assert int(np.asarray(got.metrics.n_scheduled)) == int(
        np.asarray(ref.metrics.n_scheduled)
    )
    assert int(np.asarray(got.metrics.n_completed)) == int(
        np.asarray(ref.metrics.n_completed)
    )


def test_ring_topk_merge_matches_full_gather(node_mesh):
    """ring_topk_merge == best-K prefix of sorting the full gather, on
    every shard (replication coherence), for unique keys with sentinel
    padding — the distributed-selection contract, unit-scale."""
    from fognetsimpp_tpu.parallel.taskshard import ring_topk_merge

    n, K, W = 8, 5, 3
    rng = np.random.default_rng(7)
    keys = rng.permutation(n * K * 3)[: n * K].astype(np.int32)
    x = np.stack(
        [np.arange(n * K, dtype=np.int32), rng.integers(0, 99, n * K,
                                                        dtype=np.int32),
         keys], axis=1,
    )
    # per-shard blocks arrive locally sorted ascending on the key col
    blocks = [b[np.argsort(b[:, -1], kind="stable")]
              for b in x.reshape(n, K, W)]
    xs = jnp.asarray(np.concatenate(blocks, axis=0))
    f = jax.jit(
        shard_map(
            lambda b: ring_topk_merge(b, "node", n),
            mesh=node_mesh,
            in_specs=P("node"),
            out_specs=P("node"),
            check_vma=False,
        )
    )
    got = np.asarray(f(xs)).reshape(n, K, W)
    want = x[np.argsort(x[:, -1], kind="stable")][:K]
    for s in range(n):
        np.testing.assert_array_equal(got[s], want)


# --tp --telemetry composition (per-shard phase attribution, exchange
# gauges, hist, the sharded health plane) is gated in
# tests/test_tp_telemetry.py (ISSUE 11).


def test_ring_exchange_matches_dense_reference(node_mesh):
    """ring_all_gather (ppermute ring) == the dense concatenation, for
    every shard, in global shard order."""
    n, K, C = 8, 6, 4
    x = jnp.arange(n * K * C, dtype=jnp.int32).reshape(n * K, C)

    f = jax.jit(
        shard_map(
            lambda b: ring_all_gather(b, "node", n),
            mesh=node_mesh,
            in_specs=P("node"),
            out_specs=P(None),
            check_vma=False,
        )
    )
    np.testing.assert_array_equal(np.asarray(f(x)), np.asarray(x))


def test_ring_exchange_pallas_interpret_exact(node_mesh):
    """The opt-in Pallas remote-DMA ring kernel (SNIPPETS [2]) is exact
    in interpret mode on the CPU mesh — same contract as the ppermute
    ring it replaces."""
    from fognetsimpp_tpu.ops.pallas_kernels import ring_all_gather_pallas

    n, K, C = 8, 4, 4
    x = jnp.arange(n * K * C, dtype=jnp.int32).reshape(n * K, C)
    f = jax.jit(
        shard_map(
            lambda b: ring_all_gather_pallas(b, "node", n, interpret=True),
            mesh=node_mesh,
            in_specs=P("node"),
            out_specs=P(None),
            check_vma=False,
        )
    )
    np.testing.assert_array_equal(np.asarray(f(x)), np.asarray(x))
